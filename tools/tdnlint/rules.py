"""The five tdnlint rules. Each is ``fn(project) -> [Finding]``.

Every rule encodes one bug class this repo has actually shipped and
had caught in review (docs/STATIC_ANALYSIS.md names the incidents):

* ``lock-discipline`` — ``# guarded-by: <lock>``-annotated attributes
  accessed outside ``with self.<lock>:``.
* ``tick-purity`` — blocking primitives (sleep / socket / urllib /
  subprocess / requests / http.client) reachable from callbacks the
  RuntimeSampler tick runs.
* ``metric-series-lifecycle`` — replica/target-labeled metric families
  with no ``remove``/``remove_matching`` in the defining module.
* ``admin-actuation`` — GET-mounted MetricsServer routes calling
  state-mutating pool/autoscaler verbs.
* ``jit-purity`` — jitted functions (and kernel helpers they trace)
  calling ``time.*`` / python ``random`` / ``print`` or declaring
  ``global``.
"""

from __future__ import annotations

import ast

from .core import (
    ClassInfo,
    Finding,
    FuncInfo,
    Project,
    attr_root,
    call_name,
    iter_body_nodes,
    local_bindings,
)

# ----------------------------------------------------------------------
# lock-discipline
# ----------------------------------------------------------------------

# Construction happens-before publication: no other thread can hold a
# reference while these run, so unguarded writes there are fine.
_CONSTRUCTION_METHODS = {"__init__", "__post_init__", "__new__"}


def rule_lock_discipline(project: Project):
    findings = []
    for mod in project.modules:
        for ci in mod.classes.values():
            if not ci.guarded:
                continue
            lock_names = set(ci.guarded.values())
            for mname, fi in ci.methods.items():
                if mname in _CONSTRUCTION_METHODS:
                    continue
                held = set()
                for ln in (fi.node.lineno, fi.node.lineno - 1):
                    lock = mod.holds_by_line.get(ln)
                    if lock:
                        held.add(lock)
                _visit_lock_scope(
                    mod, ci, fi, fi.node, held, lock_names, findings
                )
    return findings


def _visit_lock_scope(mod, ci, fi, node, held, lock_names, findings,
                      *, top=True):
    """Recursive walk tracking which of the class's locks are held."""
    children = ast.iter_child_nodes(node) if top else [node]
    for child in children:
        _visit_lock_node(mod, ci, fi, child, held, lock_names, findings)


def _visit_lock_node(mod, ci, fi, node, held, lock_names, findings):
    if isinstance(node, (ast.With, ast.AsyncWith)):
        newly = set(held)
        for item in node.items:
            ce = item.context_expr
            _visit_lock_node(mod, ci, fi, ce, held, lock_names, findings)
            if isinstance(ce, ast.Attribute) and isinstance(
                ce.value, ast.Name
            ) and ce.value.id in ("self", "cls") \
                    and ce.attr in lock_names:
                newly.add(ce.attr)
        for b in node.body:
            _visit_lock_node(mod, ci, fi, b, newly, lock_names, findings)
        return
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        # A closure runs later, after the enclosing with exited: only
        # its OWN caller-holds annotation counts.
        inner_held = set()
        if not isinstance(node, ast.Lambda):
            for ln in (node.lineno, node.lineno - 1):
                lock = mod.holds_by_line.get(ln)
                if lock:
                    inner_held.add(lock)
        body = node.body if isinstance(node.body, list) else [node.body]
        for b in body:
            _visit_lock_node(
                mod, ci, fi, b, inner_held, lock_names, findings
            )
        return
    if isinstance(node, ast.Attribute) and isinstance(
        node.value, ast.Name
    ) and node.value.id in ("self", "cls"):
        lock = ci.guarded.get(node.attr)
        if lock and lock not in held:
            findings.append(Finding(
                "lock-discipline", mod.relpath, node.lineno,
                fi.qualname, f"{node.attr}",
                f"{ci.name}.{node.attr} is '# guarded-by: {lock}' but "
                f"accessed in {fi.qualname} without 'with "
                f"self.{lock}:' (annotate the method '# caller-holds: "
                f"{lock}' if every caller already holds it)",
            ))
        # fall through: the value is a Name, nothing below to visit
        return
    for child in ast.iter_child_nodes(node):
        _visit_lock_node(mod, ci, fi, child, held, lock_names, findings)


# ----------------------------------------------------------------------
# tick-purity
# ----------------------------------------------------------------------

# RuntimeSampler registration verb -> the protocol method the tick
# calls on the registered object (obs/runtime.py sample_once).
_TICK_PROTOCOL = {
    "add_timeseries": "collect",
    "add_slo_tracker": "evaluate",
    "add_autoscaler": "tick",
    "add_incident_recorder": "check",
    "add_goodput": "tick",
    "add_admission_governor": "tick",
}
_BLOCKING_MODULE_ROOTS = {
    "socket", "subprocess", "urllib", "requests", "http",
}
# When a method call's receiver cannot be typed, edges go to every
# project class defining the method — unless the name is this common.
_MAX_AMBIGUOUS_TARGETS = 8


def _resolve_class_expr(project, mod, func_expr):
    """A constructor expression's class: ``Autoscaler(...)``'s func."""
    if isinstance(func_expr, ast.Name):
        name = func_expr.id
        if name in mod.classes:
            return mod.classes[name]
        ci = project.resolve_imported_class(mod, name)
        if ci is not None:
            return ci
        cands = project.class_index.get(name, [])
        if len(cands) == 1:
            return cands[0]
    elif isinstance(func_expr, ast.Attribute):
        cands = project.class_index.get(func_expr.attr, [])
        if len(cands) == 1:
            return cands[0]
    return None


def _attr_types(project, ci: ClassInfo) -> dict:
    """attr name -> ClassInfo, inferred from ``__init__``:
    ``self.a = SomeClass(...)`` or ``self.a = <param annotated
    SomeClass>`` (``X | None`` annotations take the class side)."""
    out = {}
    init = ci.methods.get("__init__")
    if init is None:
        return out
    ann = {}
    args = init.node.args
    for a in list(args.posonlyargs) + list(args.args) + list(
        args.kwonlyargs
    ):
        t = a.annotation
        if isinstance(t, ast.BinOp) and isinstance(t.op, ast.BitOr):
            t = t.left
        if isinstance(t, ast.Name):
            ann[a.arg] = t.id
        elif isinstance(t, ast.Attribute):
            ann[a.arg] = t.attr
        elif isinstance(t, ast.Constant) and isinstance(t.value, str):
            ann[a.arg] = t.value.strip('"').split(".")[-1]
    mod = ci.module
    for node in iter_body_nodes(init.node):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not (isinstance(t, ast.Attribute) and isinstance(
                t.value, ast.Name
            ) and t.value.id == "self"):
                continue
            v = node.value
            if isinstance(v, ast.Call):
                c = _resolve_class_expr(project, mod, v.func)
                if c is None:
                    c = _factory_result_class(project, v)
                if c is not None:
                    out.setdefault(t.attr, c)
            elif isinstance(v, ast.Name) and v.id in ann:
                cname = ann[v.id]
                if cname in mod.classes:
                    out.setdefault(t.attr, mod.classes[cname])
                else:
                    c = project.resolve_imported_class(mod, cname)
                    if c is None:
                        cands = project.class_index.get(cname, [])
                        c = cands[0] if len(cands) == 1 else None
                    if c is not None:
                        out.setdefault(t.attr, c)
    return out


def _factory_result_class(project, call: ast.Call):
    """Type the result of the registry's family factories: ``X =
    reg.gauge(...)`` / ``REGISTRY.counter(...)`` is a ``Metric`` —
    the analyzer knows the registry idiom, so metric mutation methods
    (``remove``, ``set``, ...) resolve exactly instead of
    over-approximating onto same-named pool methods."""
    kind = call_name(call)
    if kind and kind[0] == "attr" and kind[2] in (
        "gauge", "counter", "histogram"
    ):
        cands = project.class_index.get("Metric", [])
        if len(cands) == 1:
            return cands[0]
    return None


def _blocking_in_call(mod, node) -> str | None:
    """The blocking primitive a Call hits directly, or None."""
    kind = call_name(node)
    if kind is None:
        return None
    if kind[0] == "attr":
        _, recv, m = kind
        if m == "sleep":
            return "sleep()"
        # Module-rooted only when the root NAME really is that stdlib
        # module in this file (a local variable named ``requests`` is
        # not the requests library).
        root = attr_root(recv)
        if root in _BLOCKING_MODULE_ROOTS and root in mod.imports \
                and mod.imports[root][0] == "module" \
                and mod.imports[root][1].split(".")[0] == root:
            return f"{root}.{m}"
        return None
    _, n = kind
    entry = mod.imports.get(n)
    if entry and entry[0] == "symbol":
        top = entry[1].split(".")[0]
        if top in _BLOCKING_MODULE_ROOTS or (
            top == "time" and entry[2] == "sleep"
        ):
            return f"{entry[1]}.{entry[2]}"
    return None


def _call_edges(project, fi: FuncInfo, bindings, attr_types):
    """Outgoing call-graph edges of one function body (nested function
    bodies excluded — they run later; a nested function gets an edge
    only when called by name; thread targets never do)."""
    mod = fi.module
    edges = []
    for node in iter_body_nodes(fi.node):
        if not isinstance(node, ast.Call):
            continue
        kind = call_name(node)
        if kind is None:
            continue
        if kind[0] == "name":
            n = kind[1]
            nested = mod.functions.get(f"{fi.qualname}.<locals>.{n}")
            if nested is not None:
                edges.append((nested, node.lineno))
                continue
            target = mod.functions.get(n)
            if target is not None and target.class_name is None:
                edges.append((target, node.lineno))
                continue
            imported = project.resolve_imported_function(mod, n)
            if imported is not None:
                edges.append((imported, node.lineno))
                continue
            ci = mod.classes.get(n) or project.resolve_imported_class(
                mod, n
            )
            if ci is not None and "__init__" in ci.methods:
                edges.append((ci.methods["__init__"], node.lineno))
            continue
        _, recv, m = kind
        resolved = False
        if isinstance(recv, ast.Name) and recv.id in ("self", "cls") \
                and fi.class_name:
            own = mod.classes.get(fi.class_name)
            if own is not None and m in own.methods:
                edges.append((own.methods[m], node.lineno))
                resolved = True
            elif own is not None:
                for base in own.bases:
                    for cand in project.class_index.get(base, []):
                        if m in cand.methods:
                            edges.append(
                                (cand.methods[m], node.lineno)
                            )
                            resolved = True
        elif isinstance(recv, ast.Name):
            # A bare-name receiver is NEVER over-approximated: either
            # it resolves (local constructor binding, project import)
            # or it is a local/param of unknown — usually stdlib —
            # type, where name-matched edges were the main source of
            # false chains (``t.start()`` on a threading.Thread must
            # not become ``ReplicaPool.start``).
            resolved = True
            x = recv.id
            if x in bindings:
                b = bindings[x]
                if isinstance(b, ast.Call):
                    c = _resolve_class_expr(project, mod, b.func) \
                        or _factory_result_class(project, b)
                    if c is not None and m in c.methods:
                        edges.append((c.methods[m], node.lineno))
            elif x in mod.imports:
                entry = mod.imports[x]
                if entry[0] == "module":
                    tm = project.resolve_module(entry[1])
                    if tm is not None and m in tm.functions:
                        edges.append((tm.functions[m], node.lineno))
                else:
                    c = project.resolve_imported_class(mod, x)
                    if c is not None and m in c.methods:
                        edges.append((c.methods[m], node.lineno))
        elif isinstance(recv, ast.Attribute) and isinstance(
            recv.value, ast.Name
        ) and recv.value.id in ("self", "cls") and fi.class_name:
            t = attr_types.get(recv.attr)
            if t is not None:
                if m in t.methods:
                    edges.append((t.methods[m], node.lineno))
                resolved = True
        elif isinstance(recv, ast.Call):
            # Constructor-call receiver: resolves to a project class or
            # it is external (threading.Thread(...).start()) — never
            # over-approximated.
            resolved = True
            c = _resolve_class_expr(project, mod, recv.func) \
                or _factory_result_class(project, recv)
            if c is not None and m in c.methods:
                edges.append((c.methods[m], node.lineno))
        if not resolved:
            # Attribute receivers rooted at a LOCAL binding of unknown
            # type (``rep.proc.poll()``) stay edge-free, same as bare
            # local names; roots that are params or globals keep the
            # name-matched over-approximation (detector methods reach
            # the ring through their ``rec`` parameter).
            root = attr_root(recv)
            if root is not None and root not in ("self", "cls") \
                    and root in bindings:
                continue
            cands = project.method_index.get(m, [])
            if 0 < len(cands) <= _MAX_AMBIGUOUS_TARGETS:
                for _ci, cfi in cands:
                    edges.append((cfi, node.lineno))
    return edges


def _tick_entries(project):
    """(FuncInfo, label) tick entry points: RuntimeSampler's own
    sampling methods plus the protocol method of every class registered
    through an ``add_*`` verb (resolved from the registration site)."""
    entries = []
    for ci in project.class_index.get("RuntimeSampler", []):
        for name in ("sample_once", "_safe_sample"):
            if name in ci.methods:
                entries.append(
                    (ci.methods[name], f"RuntimeSampler.{name}")
                )
    for mod in project.modules:
        for fi in list(mod.functions.values()):
            bindings = None
            for node in iter_body_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                kind = call_name(node)
                if kind is None or kind[0] != "attr" \
                        or kind[2] not in _TICK_PROTOCOL or not node.args:
                    continue
                proto = _TICK_PROTOCOL[kind[2]]
                arg = node.args[0]
                target_cls = None
                if isinstance(arg, ast.Call):
                    target_cls = _resolve_class_expr(
                        project, mod, arg.func
                    )
                elif isinstance(arg, ast.Name):
                    if bindings is None:
                        bindings = local_bindings(fi.node)
                    b = bindings.get(arg.id)
                    if isinstance(b, ast.Call):
                        target_cls = _resolve_class_expr(
                            project, mod, b.func
                        )
                if target_cls is not None:
                    m = target_cls.methods.get(proto)
                    if m is not None:
                        entries.append(
                            (m, f"{target_cls.name}.{proto}")
                        )
                    continue
                # Unresolved registration: over-approximate with every
                # project class implementing the protocol method.
                cands = project.method_index.get(proto, [])
                if 0 < len(cands) <= _MAX_AMBIGUOUS_TARGETS:
                    for tci, tfi in cands:
                        entries.append((tfi, f"{tci.name}.{proto}"))
    return entries


def rule_tick_purity(project: Project):
    findings = []
    entries = _tick_entries(project)
    if not entries:
        return findings
    attr_type_cache: dict[int, dict] = {}
    reported = set()
    for entry, label in entries:
        # BFS with the caller chain threaded through for the message.
        queue = [(entry, [label])]
        visited = {id(entry)}
        while queue:
            fi, path = queue.pop(0)
            mod = fi.module
            bindings = local_bindings(fi.node)
            own_class = mod.classes.get(fi.class_name) \
                if fi.class_name else None
            if own_class is not None:
                key = id(own_class)
                if key not in attr_type_cache:
                    attr_type_cache[key] = _attr_types(
                        project, own_class
                    )
                attr_types = attr_type_cache[key]
            else:
                attr_types = {}
            for node in iter_body_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                prim = _blocking_in_call(mod, node)
                if prim is None:
                    continue
                key = (mod.relpath, node.lineno, prim)
                if key in reported:
                    continue
                reported.add(key)
                parts = list(path)
                if fi.qualname not in parts[-1]:
                    parts.append(fi.qualname)
                via = " -> ".join(parts)
                findings.append(Finding(
                    "tick-purity", mod.relpath, node.lineno,
                    fi.qualname, prim,
                    f"blocking call {prim} is reachable from the "
                    f"RuntimeSampler tick (via {via}); the tick must "
                    "stay non-blocking — actuate on a thread",
                ))
            for target, _line in _call_edges(
                project, fi, bindings, attr_types
            ):
                if id(target) in visited:
                    continue
                visited.add(id(target))
                nxt = path if fi.qualname in path[-1] \
                    else path + [fi.qualname]
                queue.append((target, nxt))
    return findings


# ----------------------------------------------------------------------
# metric-series-lifecycle
# ----------------------------------------------------------------------

# Label names whose value space churns with fleet membership; a family
# keyed on one of these grows unboundedly unless something prunes it.
_DYNAMIC_LABELS = {"replica", "target"}
_FAMILY_FACTORIES = {"counter", "gauge", "histogram"}


def rule_metric_lifecycle(project: Project):
    findings = []
    for mod in project.modules:
        defs = []  # (receiver_key, family, line, labels)
        removals = set()  # receiver keys with a remove/remove_matching
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                kind = call_name(node.value)
                if kind and kind[0] == "attr" \
                        and kind[2] in _FAMILY_FACTORIES:
                    labels = _const_labels(node.value)
                    dyn = labels & _DYNAMIC_LABELS
                    if dyn:
                        family = _first_str_arg(node.value)
                        for t in node.targets:
                            rk = _receiver_key(t)
                            if rk and family:
                                defs.append(
                                    (rk, family, node.lineno,
                                     sorted(dyn))
                                )
            elif isinstance(node, ast.Call):
                kind = call_name(node)
                if kind and kind[0] == "attr" and kind[2] in (
                    "remove", "remove_matching"
                ):
                    rk = _receiver_key(kind[1])
                    if rk:
                        removals.add(rk)
        for rk, family, line, dyn in defs:
            if rk in removals:
                continue
            findings.append(Finding(
                "metric-series-lifecycle", mod.relpath, line,
                enclosing_symbol_safe(mod, line), family,
                f"family '{family}' is keyed on churning label(s) "
                f"{dyn} but this module never calls remove/"
                "remove_matching on it — departed targets would "
                "expose stale series forever",
            ))
    return findings


def enclosing_symbol_safe(mod, line):
    from .core import enclosing_symbol

    return enclosing_symbol(mod, line)


def _const_labels(call: ast.Call) -> set:
    for kw in call.keywords:
        if kw.arg in ("labels", "labelnames") and isinstance(
            kw.value, (ast.Tuple, ast.List)
        ):
            out = set()
            for e in kw.value.elts:
                if isinstance(e, ast.Constant) and isinstance(
                    e.value, str
                ):
                    out.add(e.value)
            return out
    return set()


def _first_str_arg(call: ast.Call) -> str | None:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _receiver_key(node) -> tuple | None:
    if isinstance(node, ast.Name):
        return ("name", node.id)
    if isinstance(node, ast.Attribute) and isinstance(
        node.value, ast.Name
    ) and node.value.id in ("self", "cls"):
        return ("self", node.attr)
    return None


# ----------------------------------------------------------------------
# admin-actuation
# ----------------------------------------------------------------------

# ReplicaPool / Autoscaler methods that CHANGE fleet state; reachable
# from a GET route means a crawler can actuate the fleet (the PR 12
# drain/undrain/scale-were-GET bug, made structural).
_MUTATORS = {
    "drain", "undrain", "remove", "decommission", "restart_replica",
    "spawn_local", "set_override", "clear_override",
}
_ROUTE_DEPTH = 4


def rule_admin_actuation(project: Project):
    findings = []
    seen_handlers = set()
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            exprs = []
            kind = call_name(node)
            if kind and kind[0] == "attr" and kind[2] == "add_routes" \
                    and node.args:
                exprs.append(node.args[0])
            for kw in node.keywords:
                if kw.arg == "routes":
                    exprs.append(kw.value)
            for expr in exprs:
                for handler in _route_handlers(
                    project, mod, expr, node, _ROUTE_DEPTH
                ):
                    if id(handler[0]) in seen_handlers:
                        continue
                    seen_handlers.add(id(handler[0]))
                    _scan_get_handler(project, handler, findings)
    return findings


def _route_handlers(project, mod, expr, site, depth):
    """Resolve a routes-expression to [(handler_ast, FuncInfo|None,
    Module)] — dict literals, locally built+returned dicts, and
    factory-call indirection all resolve."""
    if depth <= 0:
        return []
    out = []
    if isinstance(expr, ast.Dict):
        for v in expr.values:
            out.extend(_handler_value(project, mod, v, site, depth))
    elif isinstance(expr, ast.Call):
        target = _called_function(project, mod, expr)
        if target is not None:
            out.extend(
                _factory_handlers(project, target, depth - 1)
            )
    elif isinstance(expr, ast.Name):
        # dict built in the enclosing function then mounted by name
        encl = _enclosing_function(mod, site)
        if encl is not None:
            out.extend(_dict_var_handlers(
                project, mod, encl, expr.id, depth - 1
            ))
    return out


def _handler_value(project, mod, v, site, depth):
    if isinstance(v, ast.Lambda):
        return [(v, None, mod)]
    if isinstance(v, ast.Name):
        encl = _enclosing_function(mod, site)
        if encl is not None:
            nested = mod.functions.get(
                f"{encl.qualname}.<locals>.{v.id}"
            )
            if nested is not None:
                return [(nested.node, nested, mod)]
        target = mod.functions.get(v.id)
        if target is not None:
            return [(target.node, target, mod)]
        imported = project.resolve_imported_function(mod, v.id)
        if imported is not None:
            return [(imported.node, imported, imported.module)]
        return []
    if isinstance(v, ast.Call):
        # A factory returning ONE handler closure
        # (fleet_trace_route(pool)) — its returned nested functions.
        target = _called_function(project, mod, v)
        if target is not None:
            return _returned_closures(project, target, depth - 1)
    return []


def _called_function(project, mod, call: ast.Call):
    kind = call_name(call)
    if kind is None:
        return None
    if kind[0] == "name":
        target = mod.functions.get(kind[1])
        if target is not None and target.class_name is None:
            return target
        return project.resolve_imported_function(mod, kind[1])
    return None


def _enclosing_function(mod, node):
    from .core import enclosing_symbol

    qual = enclosing_symbol(mod, node.lineno)
    return mod.functions.get(qual)


def _factory_handlers(project, fi: FuncInfo, depth):
    """Handlers of a factory that RETURNS a routes dict."""
    mod = fi.module
    out = []
    for node in iter_body_nodes(fi.node):
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Dict):
                for v in node.value.values:
                    out.extend(_handler_value(
                        project, mod, v, fi.node, depth
                    ))
            elif isinstance(node.value, ast.Name):
                out.extend(_dict_var_handlers(
                    project, mod, fi, node.value.id, depth
                ))
    return out


def _dict_var_handlers(project, mod, fi: FuncInfo, varname, depth):
    """A routes dict built locally: its literal values, plus
    ``routes[...] = f`` subscript-assigns, plus ``routes.update(F())``
    factory merges."""
    if depth <= 0:
        return []
    out = []
    for node in iter_body_nodes(fi.node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == varname \
                        and isinstance(node.value, ast.Dict):
                    for v in node.value.values:
                        out.extend(_handler_value(
                            project, mod, v, fi.node, depth
                        ))
                elif isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Name
                ) and t.value.id == varname:
                    out.extend(_handler_value(
                        project, mod, node.value, fi.node, depth
                    ))
        elif isinstance(node, ast.Call):
            kind = call_name(node)
            if kind and kind[0] == "attr" and kind[2] == "update" \
                    and isinstance(kind[1], ast.Name) \
                    and kind[1].id == varname and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Dict):
                    for v in arg.values:
                        out.extend(_handler_value(
                            project, mod, v, fi.node, depth
                        ))
                elif isinstance(arg, ast.Call):
                    target = _called_function(project, mod, arg)
                    if target is not None:
                        out.extend(_factory_handlers(
                            project, target, depth - 1
                        ))
    return out


def _returned_closures(project, fi: FuncInfo, depth):
    mod = fi.module
    out = []
    for node in iter_body_nodes(fi.node):
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Name):
                nested = mod.functions.get(
                    f"{fi.qualname}.<locals>.{node.value.id}"
                )
                if nested is not None:
                    out.append((nested.node, nested, mod))
            elif isinstance(node.value, ast.Lambda):
                out.append((node.value, None, mod))
    return out


def _scan_get_handler(project, handler, findings):
    node, fi, mod = handler
    qual = fi.qualname if fi is not None else "<lambda>"
    name = fi.name if fi is not None else "<lambda>"
    # The handler body plus one level of local helper calls.
    bodies = [node]
    if fi is not None:
        for n in iter_body_nodes(node):
            if isinstance(n, ast.Call):
                kind = call_name(n)
                if kind and kind[0] == "name":
                    for cand in (
                        f"{fi.qualname}.<locals>.{kind[1]}",
                        kind[1],
                    ):
                        helper = mod.functions.get(cand)
                        if helper is not None \
                                and helper.class_name is None:
                            bodies.append(helper.node)
                            break
                    # also: helpers nested in the same factory
                    if "<locals>" in fi.qualname:
                        parent = fi.qualname.rsplit(".<locals>.", 1)[0]
                        helper = mod.functions.get(
                            f"{parent}.<locals>.{kind[1]}"
                        )
                        if helper is not None:
                            bodies.append(helper.node)
    seen = set()
    for body in bodies:
        if id(body) in seen:
            continue
        seen.add(id(body))
        walker = ast.walk(body) if isinstance(body, ast.Lambda) \
            else iter_body_nodes(body, skip_nested=False)
        for n in walker:
            if not isinstance(n, ast.Call):
                continue
            kind = call_name(n)
            if kind and kind[0] == "attr" and kind[2] in _MUTATORS:
                findings.append(Finding(
                    "admin-actuation", mod.relpath, n.lineno,
                    qual, f"{name}:{kind[2]}",
                    f"GET-mounted route handler '{name}' calls "
                    f"state-mutating '{kind[2]}()' — fleet actuation "
                    "belongs on post_routes=/add_post_routes (a GET "
                    "sweep must never actuate)",
                ))
    return findings


# ----------------------------------------------------------------------
# jit-purity
# ----------------------------------------------------------------------

_KERNEL_DIR_MARKERS = ("/kernels/", "/models/")


def _is_jit_expr(node) -> bool:
    return (
        isinstance(node, ast.Attribute) and node.attr == "jit"
    ) or (isinstance(node, ast.Name) and node.id == "jit")


def _jit_target_names(call: ast.Call):
    """Function names a ``jax.jit(...)`` call compiles: the bare
    argument, or the first argument of a partial(...) wrapper."""
    if not call.args:
        return []
    arg = call.args[0]
    if isinstance(arg, ast.Name):
        return [arg.id]
    if isinstance(arg, ast.Call):
        f = arg.func
        is_partial = (
            isinstance(f, ast.Name) and f.id == "partial"
        ) or (isinstance(f, ast.Attribute) and f.attr == "partial")
        if is_partial and arg.args and isinstance(
            arg.args[0], ast.Name
        ):
            return [arg.args[0].id]
    return []


def rule_jit_purity(project: Project):
    findings = []
    for mod in project.modules:
        jitted: dict[str, FuncInfo] = {}

        def mark(name, near_line):
            # nearest definition: nested defs first (jax.jit(step)
            # inside a factory refers to the local step), then module
            # level.
            best = None
            for qual, fi in mod.functions.items():
                if fi.name != name:
                    continue
                if best is None or abs(
                    fi.node.lineno - near_line
                ) < abs(best.node.lineno - near_line):
                    best = fi
            if best is not None:
                jitted.setdefault(best.qualname, best)

        for qual, fi in mod.functions.items():
            for dec in getattr(fi.node, "decorator_list", ()):
                if _is_jit_expr(dec):
                    jitted.setdefault(qual, fi)
                elif isinstance(dec, ast.Call):
                    f = dec.func
                    is_partial = (
                        isinstance(f, ast.Name) and f.id == "partial"
                    ) or (
                        isinstance(f, ast.Attribute)
                        and f.attr == "partial"
                    )
                    if (is_partial and dec.args
                            and _is_jit_expr(dec.args[0])) \
                            or _is_jit_expr(f):
                        jitted.setdefault(qual, fi)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_jit_expr(node.func):
                for name in _jit_target_names(node):
                    mark(name, node.lineno)
        # Kernel modules: helpers a jitted function traces into are
        # under the same purity contract.
        if any(m in "/" + mod.relpath for m in _KERNEL_DIR_MARKERS):
            work = list(jitted.values())
            while work:
                fi = work.pop()
                for node in iter_body_nodes(fi.node,
                                            skip_nested=False):
                    if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Name
                    ):
                        helper = mod.functions.get(node.func.id)
                        if helper is not None \
                                and helper.qualname not in jitted:
                            jitted[helper.qualname] = helper
                            work.append(helper)
        for qual, fi in sorted(jitted.items()):
            findings.extend(_jit_violations(mod, fi))
    return findings


def _jit_violations(mod, fi: FuncInfo):
    out = []
    py_random = any(
        entry == ("module", "random") for entry in mod.imports.values()
    )
    for node in iter_body_nodes(fi.node, skip_nested=False):
        if isinstance(node, ast.Global):
            out.append(Finding(
                "jit-purity", mod.relpath, node.lineno, fi.qualname,
                "global",
                f"jitted function {fi.qualname} declares 'global' — "
                "mutating module state under trace runs once at "
                "compile time, not per call",
            ))
            continue
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id == "print":
            out.append(Finding(
                "jit-purity", mod.relpath, node.lineno, fi.qualname,
                "print",
                f"print() inside jitted function {fi.qualname} fires "
                "at trace time only — use jax.debug.print for "
                "per-call output",
            ))
            continue
        root = attr_root(f) if isinstance(f, ast.Attribute) else None
        if root == "time":
            out.append(Finding(
                "jit-purity", mod.relpath, node.lineno, fi.qualname,
                f"time.{f.attr}",
                f"time.{f.attr}() inside jitted function "
                f"{fi.qualname} is evaluated once at trace time and "
                "baked into the compiled program",
            ))
        elif root == "random" and py_random:
            out.append(Finding(
                "jit-purity", mod.relpath, node.lineno, fi.qualname,
                f"random.{f.attr}",
                f"python random.{f.attr}() inside jitted function "
                f"{fi.qualname} draws once at trace time — use "
                "jax.random with an explicit key",
            ))
    return out


RULES = {
    "lock-discipline": rule_lock_discipline,
    "tick-purity": rule_tick_purity,
    "metric-series-lifecycle": rule_metric_lifecycle,
    "admin-actuation": rule_admin_actuation,
    "jit-purity": rule_jit_purity,
}
