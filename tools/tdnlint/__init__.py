"""tdnlint — machine-checked project invariants for tpu_dist_nn.

A stdlib-only AST analyzer with five project-specific rules (see
docs/STATIC_ANALYSIS.md for the catalog and workflow):

* ``lock-discipline``         — ``# guarded-by:`` attrs need their lock
* ``tick-purity``             — no blocking calls on the sampler tick
* ``metric-series-lifecycle`` — churning-label families must be pruned
* ``admin-actuation``         — GET routes must not mutate fleet state
* ``jit-purity``              — jitted code: no time/random/print/global

Run it as ``tdn lint [paths...]``, ``python tools/tdnlint`` from the
repo root, or programmatically via :func:`run_lint` / :func:`main`.
Exit codes: 0 clean (baselined findings allowed), 1 non-baselined
findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

from .core import (  # noqa: F401
    Finding,
    LintError,
    load_baseline,
    run_lint,
    save_baseline,
)
from .rules import RULES  # noqa: F401

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)


def summary_line(result: dict) -> str:
    new = len(result["new"])
    return (
        f"tdnlint: {new} finding{'s' if new != 1 else ''} "
        f"({len(result['baselined'])} baselined, "
        f"{result['suppressed_total']} suppressed) "
        f"across {result['files']} files"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tdnlint",
        description="machine-checked tpu_dist_nn invariants "
                    "(docs/STATIC_ANALYSIS.md)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/packages to scan (default: the "
                         "tpu_dist_nn package next to this repo's "
                         "tools/)")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="RULE",
                    help="run only this rule (repeatable); default all")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON of grandfathered findings "
                         "(default: tools/tdnlint/baseline.json; pass "
                         "'' to disable)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current finding "
                         "set (existing justifications are kept; new "
                         "entries get a TODO)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule ids and exit")
    ap.add_argument("--json", action="store_true",
                    help="also print one machine-readable JSON line")
    args = ap.parse_args(argv)
    if args.list_rules:
        for rule in RULES:
            print(rule)
        return 0
    if args.rule:
        unknown = set(args.rule) - set(RULES)
        if unknown:
            print(f"error: unknown rule(s) {sorted(unknown)}; have "
                  f"{sorted(RULES)}", file=sys.stderr)
            return 2
    paths = args.paths or [os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))), "tpu_dist_nn",
    )]
    for p in paths:
        if not os.path.exists(p):
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2
    baseline_path = args.baseline or None
    try:
        result = run_lint(paths, rules=args.rule,
                          baseline_path=baseline_path)
    except LintError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.update_baseline:
        if not baseline_path:
            print("error: --update-baseline needs --baseline",
                  file=sys.stderr)
            return 2
        old = load_baseline(baseline_path)
        save_baseline(baseline_path, result["all"], old)
        print(f"baseline updated: {len(result['all'])} entries -> "
              f"{baseline_path}")
        return 0
    for f in result["new"]:
        print(f.render())
    for fp in result["stale_baseline"]:
        print(f"stale baseline entry (matches nothing): {fp}",
              file=sys.stderr)
    print(summary_line(result))
    if args.json:
        import json as _json

        print(_json.dumps({
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "symbol": f.symbol, "detail": f.detail,
                 "fingerprint": f.fingerprint}
                for f in result["new"]
            ],
            "baselined": len(result["baselined"]),
            "suppressed": result["suppressed_total"],
            "stale_baseline": result["stale_baseline"],
            "files": result["files"],
        }))
    return 1 if result["new"] else 0
