"""tdnlint core: project index, findings, suppressions, baseline, runner.

The analyzer is stdlib-only (``ast`` + ``tokenize``-free line scans) and
deliberately project-shaped: it knows this repo's idioms (the
``RuntimeSampler`` tick, ``MetricsServer`` route mounting, the metric
registry) so its five rules can encode invariants a generic linter
cannot express. See docs/STATIC_ANALYSIS.md for the rule catalog and
the suppression / baseline workflow.

Vocabulary the rules share:

* **Finding** — one violation: rule id, file, line, enclosing symbol,
  a stable ``detail`` discriminator, and a human message. Its
  ``fingerprint`` (rule:path:symbol:detail) is deliberately
  line-number-free so a baseline survives unrelated edits to the file.
* **Suppression** — ``# tdnlint: disable=<rule>[,<rule>...]`` (or
  ``disable=all``) on the first line of the flagged statement.
* **Baseline** — ``baseline.json`` next to this package: grandfathered
  findings, each with a one-line justification. Non-baselined findings
  fail the run; stale entries (matching nothing) are reported so the
  file cannot rot.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

class LintError(Exception):
    """A scan target could not be read or parsed. Raised (not
    SystemExit) so library callers — run_lint from cli.py, bench_gate's
    fail-safe lint header, tests — can degrade instead of dying; only
    tdnlint.main() converts it to an exit code."""


_DISABLE_RE = re.compile(r"#\s*tdnlint:\s*disable=([A-Za-z0-9_,\- ]+)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_HOLDS_RE = re.compile(r"#\s*caller-holds:\s*([A-Za-z_][A-Za-z0-9_]*)")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # scan-root-relative, posix separators
    line: int
    symbol: str  # enclosing qualname ("Autoscaler.tick", "<module>")
    detail: str  # stable discriminator (attr name, family name, ...)
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}:{self.detail}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        )


@dataclasses.dataclass
class FuncInfo:
    """One function or method (nested functions included, with
    ``parent.<locals>.name`` qualnames)."""

    name: str
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    module: "Module"
    class_name: str | None = None  # owning class for methods


@dataclasses.dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    module: "Module"
    bases: list  # base-class name strings (best effort)
    methods: dict  # name -> FuncInfo
    # lock-discipline annotations: attr name -> lock name
    guarded: dict = dataclasses.field(default_factory=dict)


class Module:
    """One parsed source file plus its line-keyed comment directives."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of disabled rule ids ("all" disables every rule)
        self.disable: dict[int, set] = {}
        # line -> "guarded-by" lock name / "caller-holds" lock name
        self.guarded_by_line: dict[int, str] = {}
        self.holds_by_line: dict[int, str] = {}
        for i, text in enumerate(self.lines, start=1):
            if "#" not in text:
                continue
            m = _DISABLE_RE.search(text)
            if m:
                self.disable[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
            m = _GUARDED_RE.search(text)
            if m:
                self.guarded_by_line[i] = m.group(1)
            m = _HOLDS_RE.search(text)
            if m:
                self.holds_by_line[i] = m.group(1)
        # import map: local name -> ("module", "pkg.mod") for
        # ``import pkg.mod [as name]``, ("symbol", "pkg.mod", "sym")
        # for ``from pkg.mod import sym [as name]``.
        self.imports: dict[str, tuple] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    self.imports[local] = ("module", alias.name)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    local = alias.asname or alias.name
                    self.imports[local] = (
                        "symbol", node.module, alias.name
                    )
        self.functions: dict[str, FuncInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self._index()

    def _index(self) -> None:
        def walk_func(node, qual_prefix, class_name):
            qual = (
                f"{qual_prefix}.{node.name}" if qual_prefix else node.name
            )
            info = FuncInfo(node.name, qual, node, self, class_name)
            self.functions[qual] = info
            for child in node.body:
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    walk_func(child, f"{qual}.<locals>", None)
            return info

        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_func(node, "", None)
            elif isinstance(node, ast.ClassDef):
                bases = []
                for b in node.bases:
                    if isinstance(b, ast.Name):
                        bases.append(b.id)
                    elif isinstance(b, ast.Attribute):
                        bases.append(b.attr)
                ci = ClassInfo(node.name, node, self, bases, {})
                self.classes[node.name] = ci
                for child in node.body:
                    if isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        fi = walk_func(child, node.name, node.name)
                        ci.methods[child.name] = fi
                self._collect_guarded(ci)

    def _collect_guarded(self, ci: ClassInfo) -> None:
        """Attach ``# guarded-by:`` annotations to the attributes whose
        (first) assignment line carries them — class-body attributes
        and ``self.X = ...`` statements in any method both count."""

        def note(stmt, attr_names):
            # Trailing comment on the assignment's first line, or a
            # PURE comment line directly above it (multi-target
            # assigns) — a previous statement's trailing comment must
            # not leak onto the next attribute.
            lock = self.guarded_by_line.get(stmt.lineno)
            if not lock:
                above = stmt.lineno - 1
                if 1 <= above <= len(self.lines) and self.lines[
                    above - 1
                ].strip().startswith("#"):
                    lock = self.guarded_by_line.get(above)
            if lock:
                for a in attr_names:
                    ci.guarded.setdefault(a, lock)

        for node in ast.walk(ci.node):
            if isinstance(node, ast.Assign):
                attrs = []
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and isinstance(
                        t.value, ast.Name
                    ) and t.value.id in ("self", "cls"):
                        attrs.append(t.attr)
                    elif isinstance(t, ast.Name):
                        attrs.append(t.id)  # class-body attribute
                if attrs:
                    note(node, attrs)
            elif isinstance(node, ast.AnnAssign):
                t = node.target
                if isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name
                ) and t.value.id in ("self", "cls"):
                    note(node, [t.attr])
                elif isinstance(t, ast.Name):
                    note(node, [t.id])

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.disable.get(line)
        return bool(rules) and (rule in rules or "all" in rules)


class Project:
    """Every module under the scan roots, plus cross-module indexes."""

    def __init__(self, roots):
        self.modules: list[Module] = []
        self.by_modname: dict[str, Module] = {}
        for root in roots:
            root = os.path.abspath(root)
            base = os.path.basename(root.rstrip(os.sep))
            if os.path.isfile(root):
                self._load(root, base)
                continue
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                )
                for fname in sorted(filenames):
                    if not fname.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fname)
                    self._load(
                        path,
                        os.path.join(base, os.path.relpath(path, root)),
                    )
        # method name -> [(ClassInfo, FuncInfo)] across the project
        self.method_index: dict[str, list] = {}
        # class name -> [ClassInfo]
        self.class_index: dict[str, list] = {}
        for mod in self.modules:
            for ci in mod.classes.values():
                self.class_index.setdefault(ci.name, []).append(ci)
                for name, fi in ci.methods.items():
                    self.method_index.setdefault(name, []).append(
                        (ci, fi)
                    )

    def _load(self, path: str, rel: str) -> None:
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            mod = Module(path, rel, source)
        except (OSError, SyntaxError, ValueError) as e:
            raise LintError(f"cannot parse {path}: {e}") from e
        self.modules.append(mod)
        # dotted module name guess from the relpath (import resolution)
        dotted = mod.relpath[:-3].replace("/", ".")
        if dotted.endswith(".__init__"):
            dotted = dotted[: -len(".__init__")]
        self.by_modname[dotted] = mod

    def resolve_module(self, dotted: str) -> Module | None:
        """A project module by dotted name, tolerating the scan root
        being a package prefix (``tpu_dist_nn.obs.slo`` resolves when
        the scan indexed ``tpu_dist_nn/obs/slo.py``)."""
        if dotted in self.by_modname:
            return self.by_modname[dotted]
        for name, mod in self.by_modname.items():
            if dotted.endswith("." + name) or name.endswith("." + dotted):
                return mod
        return None

    def resolve_imported_function(self, mod: Module,
                                  local: str) -> FuncInfo | None:
        """``from pkg.mod import f`` -> the project FuncInfo for f."""
        entry = mod.imports.get(local)
        if not entry or entry[0] != "symbol":
            return None
        target = self.resolve_module(entry[1])
        if target is None:
            return None
        return target.functions.get(entry[2])

    def resolve_imported_class(self, mod: Module,
                               local: str) -> ClassInfo | None:
        entry = mod.imports.get(local)
        if not entry or entry[0] != "symbol":
            return None
        target = self.resolve_module(entry[1])
        if target is None:
            return None
        return target.classes.get(entry[2])


# --------------------------------------------------------------- helpers


def call_name(node: ast.Call):
    """-> ("name", n) | ("attr", receiver_node, attr) | None."""
    f = node.func
    if isinstance(f, ast.Name):
        return ("name", f.id)
    if isinstance(f, ast.Attribute):
        return ("attr", f.value, f.attr)
    return None


def attr_root(node) -> str | None:
    """Leftmost Name of an attribute chain (``a.b.c`` -> "a")."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def enclosing_symbol(mod: Module, line: int) -> str:
    """Qualname of the innermost function/class containing ``line``."""
    best = "<module>"
    best_span = None
    for qual, fi in mod.functions.items():
        node = fi.node
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= line <= end:
            span = end - node.lineno
            if best_span is None or span < best_span:
                best, best_span = qual, span
    if best == "<module>":
        for name, ci in mod.classes.items():
            end = getattr(ci.node, "end_lineno", ci.node.lineno)
            if ci.node.lineno <= line <= end:
                return name
    return best


def iter_body_nodes(func_node, *, skip_nested: bool = True):
    """Walk a function body; by default do NOT descend into nested
    function/lambda bodies (they execute later — off the path being
    analyzed — and get edges only when called by name)."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        if skip_nested and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def local_bindings(func_node) -> dict:
    """Name -> the ast node it was last assigned from (Call nodes kept;
    everything else maps to None, meaning "locally bound, type
    unknown"). For-targets, comprehension targets, and with-as targets
    all count as local bindings."""
    out: dict[str, ast.AST | None] = {}
    for node in iter_body_nodes(func_node):
        if isinstance(node, ast.Assign):
            value = node.value if isinstance(node.value, ast.Call) \
                else None
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, value)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        if isinstance(e, ast.Name):
                            out.setdefault(e.id, None)
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            out.setdefault(
                node.target.id,
                node.value if isinstance(node.value, ast.Call) else None,
            )
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            t = node.target
            names = [t] if isinstance(t, ast.Name) else (
                t.elts if isinstance(t, (ast.Tuple, ast.List)) else []
            )
            for e in names:
                if isinstance(e, ast.Name):
                    out.setdefault(e.id, None)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.optional_vars, ast.Name):
                    out.setdefault(item.optional_vars.id, None)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                if isinstance(gen.target, ast.Name):
                    out.setdefault(gen.target.id, None)
    return out


# --------------------------------------------------------------- baseline


def load_baseline(path: str) -> dict:
    """-> {fingerprint: justification}; empty file/missing = empty."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    out = {}
    for entry in doc.get("findings", ()):
        out[entry["fingerprint"]] = entry.get("justification", "")
    return out


def save_baseline(path: str, findings, old: dict) -> None:
    entries = []
    seen = set()
    for f in findings:
        if f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        entries.append({
            "fingerprint": f.fingerprint,
            "justification": old.get(
                f.fingerprint, "TODO: justify this grandfathered finding"
            ),
        })
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": entries}, f, indent=2)
        f.write("\n")


# ----------------------------------------------------------------- runner


def run_lint(paths, *, rules=None, baseline_path: str | None = None):
    """Parse ``paths``, run every (or the named) rules, split findings
    against the baseline. -> dict with ``new``, ``baselined``,
    ``stale_baseline``, ``suppressed_total``, ``files``."""
    from . import rules as rules_mod

    project = Project(paths)
    selected = rules_mod.RULES if rules is None else {
        k: v for k, v in rules_mod.RULES.items() if k in rules
    }
    raw: list[Finding] = []
    for rule_id, rule_fn in selected.items():
        raw.extend(rule_fn(project))
    findings = []
    suppressed = 0
    mod_by_rel = {m.relpath: m for m in project.modules}
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        mod = mod_by_rel.get(f.path)
        if mod is not None and mod.suppressed(f.rule, f.line):
            suppressed += 1
            continue
        findings.append(f)
    baseline = load_baseline(baseline_path) if baseline_path else {}
    new = [f for f in findings if f.fingerprint not in baseline]
    matched = {f.fingerprint for f in findings} & set(baseline)
    stale = sorted(set(baseline) - matched)
    return {
        "new": new,
        "all": findings,
        "baselined": sorted(matched),
        "baseline": baseline,
        "stale_baseline": stale,
        "suppressed_total": suppressed,
        "files": len(project.modules),
    }
