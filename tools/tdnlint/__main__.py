import sys

if __package__ in (None, ""):
    # ``python tools/tdnlint`` (path execution): register the package
    # by file location so the relative imports inside it resolve.
    import importlib.util
    import os

    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        "tdnlint", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["tdnlint"] = mod
    spec.loader.exec_module(mod)
    sys.exit(mod.main())
else:
    from . import main

    sys.exit(main())
